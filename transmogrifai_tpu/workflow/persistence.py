"""Workflow-model persistence: save/load a fitted DAG.

TPU-native re-design of the reference model writer/reader
(core/src/main/scala/com/salesforce/op/{OpWorkflowModelWriter.scala:52-123,
OpWorkflowModelReader.scala} and the stage writer/reader
features/.../stages/{OpPipelineStageWriter.scala:78-120,
OpPipelineStageReader.scala:89-135}).

Layout: a directory with
- ``op-model.json`` — result-feature uids, the full feature DAG (uids,
  types, parent links), and every stage's class name + ctor args
  (the reference's reflective ctor capture becomes the explicit
  ``_ctor_args`` record taken at construction, stages/base.py),
- ``arrays.npz`` — every numpy array referenced from ctor args (model
  coefficients, tree heaps, …), keyed ``<stage-uid>/<path>``.

Functions (``extract_fn`` of raw-feature generators, ``fn`` of lambda
transformers) round-trip only when importable (``module:qualname``);
otherwise they are dropped and the generator falls back to dict/attr
lookup by feature name — the reference has the same limitation (it
stores the lambda's *source text* for display only, and requires the
class to be on the classpath to reload).
"""
from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, List, Tuple

import numpy as np

from ..features.feature import Feature
from ..stages.base import Estimator, PipelineStage, stage_class_by_name
from ..types.base import feature_type_by_name
from ..utils.vector_meta import VectorMetadata

__all__ = ["save_model", "load_model", "stage_to_json", "stage_from_json",
           "encode_value", "decode_value"]

MODEL_JSON = "op-model.json"
ARRAYS_NPZ = "arrays.npz"
#: bumped to 2 when $stage/$selsummary nested encodings were added
#: (selector-trained models); readers reject formats newer than this
#: instead of mis-decoding them into plain dicts
MODEL_FORMAT_VERSION = 2


# ---------------------------------------------------------------------------
# value encoding (replaces reference AnyValueTypes,
# OpPipelineStageReadWriteShared.scala)
# ---------------------------------------------------------------------------

def resolve_importable_fn(fn) -> "Optional[str]":
    """``"module:qualname"`` for a function another process can
    re-import, else None. Functions defined in a script run as
    ``__main__`` are re-resolved through the script's module name —
    a recorded ``__main__:f`` would import the LOADER's main module
    and fail (or worse, silently bind a different f)."""
    mod = getattr(fn, "__module__", None)
    qual = getattr(fn, "__qualname__", "")
    if not (mod and qual) or "<" in qual:
        return None
    if mod != "__main__":
        return f"{mod}:{qual}"
    import importlib.util
    import sys as _sys
    f = getattr(_sys.modules.get("__main__"), "__file__", None)
    stem = os.path.splitext(os.path.basename(f))[0] if f else None
    if not stem:
        return None
    # Resolve WITHOUT importing: import_module(stem) would re-execute the
    # running script's top-level code mid-train (and a name collision
    # would silently bind a DIFFERENT module's f). find_spec only
    # consults the finders; requiring the spec to point back at the
    # running script guarantees `stem:qual` reloads THIS function.
    try:
        spec = importlib.util.find_spec(stem)
    except (ImportError, ValueError, AttributeError):
        return None   # script not importable by name -> honest drop
    if spec is None or not spec.origin:
        return None
    if os.path.abspath(spec.origin) != os.path.abspath(f):
        return None   # stem resolves to a different module -> wrong f
    return f"{stem}:{qual}"


def _jsonify(v: Any) -> Any:
    """Pure-JSON copy of a nested dict/list payload: numpy scalars to
    python scalars, arrays to lists."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, dict):
        # keys too: np.int64 topNs etc. — json.dump rejects numpy keys
        return {(k.item() if isinstance(k, np.generic) else k):
                _jsonify(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonify(x) for x in v]
    return v


def encode_value(v: Any, arrays: Dict[str, np.ndarray], key: str) -> Any:
    """JSON-safe encoding; arrays are swapped for ``{"$array": key}`` refs
    stored in the npz sidecar."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        arrays[key] = v
        return {"$array": key}
    if hasattr(v, "__array__") and not isinstance(v, (list, tuple, dict)):
        # device arrays (jax) captured in ctor args before np conversion
        arrays[key] = np.asarray(v)
        return {"$array": key}
    if isinstance(v, (list, tuple)):
        return {"$seq": [encode_value(x, arrays, f"{key}/{i}")
                         for i, x in enumerate(v)],
                "$tuple": isinstance(v, tuple)}
    if isinstance(v, dict):
        return {"$dict": {str(k): encode_value(x, arrays, f"{key}/{k}")
                          for k, x in v.items()}}
    if isinstance(v, PipelineStage):
        # nested fitted stage — e.g. SelectedModel.inner, the winning
        # model a trained ModelSelector wraps (reference SelectedModel's
        # sparkMlStage save, ModelSelectorReaderWriter semantics)
        return {"$stage": stage_to_json(v, arrays)}
    from ..selector.selector import ModelSelectorSummary
    if isinstance(v, ModelSelectorSummary):
        # param/grid dicts inside the summary can carry numpy scalars
        # (e.g. np.int64 depths from an np.arange grid) — json.dump
        # rejects those, so sanitize the whole payload
        return {"$selsummary": _jsonify(v.to_json())}
    if isinstance(v, type):
        from ..types.base import FeatureType
        if issubclass(v, FeatureType):
            return {"$ftype": v.__name__}
        raise ValueError(f"Cannot serialize class {v!r} at {key}")
    if isinstance(v, VectorMetadata):
        return {"$vmeta": v.to_json()}
    if callable(v):
        return {"$fn": resolve_importable_fn(v)}  # None = dropped
    raise ValueError(
        f"Cannot serialize ctor arg of type {type(v).__name__} at {key}")


def decode_value(v: Any, arrays: Dict[str, np.ndarray]) -> Any:
    if isinstance(v, dict):
        if "$array" in v:
            return np.asarray(arrays[v["$array"]])
        if "$seq" in v:
            seq = [decode_value(x, arrays) for x in v["$seq"]]
            return tuple(seq) if v.get("$tuple") else seq
        if "$dict" in v:
            return {k: decode_value(x, arrays) for k, x in v["$dict"].items()}
        if "$ftype" in v:
            return feature_type_by_name(v["$ftype"])
        if "$vmeta" in v:
            return VectorMetadata.from_json(v["$vmeta"])
        if "$stage" in v:
            return stage_from_json(v["$stage"], arrays)
        if "$selsummary" in v:
            from ..selector.selector import ModelSelectorSummary
            return ModelSelectorSummary.from_json(v["$selsummary"])
        if "$fn" in v:
            if v["$fn"] is None:
                return None
            mod, qual = v["$fn"].split(":", 1)
            obj = importlib.import_module(mod)
            for part in qual.split("."):
                obj = getattr(obj, part)
            return obj
        return {k: decode_value(x, arrays) for k, x in v.items()}
    if isinstance(v, list):
        return [decode_value(x, arrays) for x in v]
    return v


# ---------------------------------------------------------------------------
# stage serde
# ---------------------------------------------------------------------------

def stage_to_json(stage: PipelineStage, arrays: Dict[str, np.ndarray]) -> dict:
    """(reference OpPipelineStageWriter.scala:78-120)"""
    params = stage.get_params()
    params.pop("uid", None)
    d = {
        "className": type(stage).__name__,
        "uid": stage.uid,
        "operationName": stage.operation_name,
        "ctorArgs": {k: encode_value(v, arrays, f"{stage.uid}/{k}")
                     for k, v in params.items()},
    }
    pec = getattr(stage, "parent_estimator_class", None)
    if pec:
        d["parentEstimatorClass"] = pec
    vmeta = getattr(stage, "vector_metadata", None)
    if isinstance(vmeta, VectorMetadata):
        d["vectorMetadata"] = vmeta.to_json()
    return d


def stage_from_json(d: dict, arrays: Dict[str, np.ndarray]) -> PipelineStage:
    """(reference OpPipelineStageReader.scala:89-135)"""
    cls = stage_class_by_name(d["className"])
    kwargs = {k: decode_value(v, arrays) for k, v in d["ctorArgs"].items()}
    kwargs["uid"] = d["uid"]
    if kwargs.get("extract_fn", "missing") is None:
        kwargs.pop("extract_fn")  # fall back to by-name record lookup
    if cls.__name__ == "LambdaTransformer" and kwargs.get("fn") is None:
        raise ValueError(
            f"Stage {d['uid']}: LambdaTransformer function was not "
            "importable at save time and cannot be restored")
    stage = cls(**kwargs)
    stage.operation_name = d.get("operationName", stage.operation_name)
    if "parentEstimatorClass" in d:
        stage.parent_estimator_class = d["parentEstimatorClass"]
    if "vectorMetadata" in d:
        stage.vector_metadata = VectorMetadata.from_json(d["vectorMetadata"])
    return stage


# ---------------------------------------------------------------------------
# feature DAG serde (reference FeatureJsonHelper)
# ---------------------------------------------------------------------------

def _feature_to_json(f: Feature) -> dict:
    return {
        "name": f.name,
        "uid": f.uid,
        "typeName": f.ftype.__name__,
        "isResponse": f.is_response,
        "originStageUid": f.origin_stage.uid if f.origin_stage else None,
        "parentUids": [p.uid for p in f.parents],
    }


def _collect_features_topo(result_features) -> List[Feature]:
    """All DAG features, parents before children."""
    seen: Dict[str, Feature] = {}
    order: List[Feature] = []

    def go(f: Feature):
        if f.uid in seen:
            return
        seen[f.uid] = f
        for p in f.parents:
            go(p)
        order.append(f)

    for rf in result_features:
        go(rf)
    return order


# ---------------------------------------------------------------------------
# model save / load
# ---------------------------------------------------------------------------

def _fsync_file(fpath: str) -> None:
    fd = os.open(fpath, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def save_model(model, path: str) -> None:
    """Write a fitted WorkflowModel to ``path`` (a directory)
    (reference OpWorkflowModelWriter.toJson:75-120).

    ATOMIC: the files are staged into a sibling temp directory
    (fsync'd) and swapped in with ``os.replace``/``os.rename`` — a
    crash mid-save (VM preemption, OOM-kill) leaves either the previous
    intact model or no model at ``path``, never a half-written
    directory. A leftover ``<path>.tmp-save*`` staging dir is the
    crash's only trace, and ``load_model`` rejects it with a clear
    error instead of mis-loading."""
    feats = _collect_features_topo(model.result_features)
    for f in feats:
        if f.origin_stage is not None and isinstance(f.origin_stage,
                                                     Estimator):
            raise ValueError(
                f"Feature {f.name!r} still points at unfitted estimator "
                f"{f.origin_stage!r}; save the model returned by train()")
    arrays: Dict[str, np.ndarray] = {}
    stages, staged = [], set()
    for f in feats:
        s = f.origin_stage
        if s is not None and s.uid not in staged:
            staged.add(s.uid)
            stages.append(stage_to_json(s, arrays))
    from ..utils.version import version_info
    rff = getattr(model, "raw_feature_filter_results", None)
    doc = {
        "formatVersion": MODEL_FORMAT_VERSION,
        "versionInfo": version_info().to_json(),
        "resultFeatureUids": [f.uid for f in model.result_features],
        "features": [_feature_to_json(f) for f in feats],
        "stages": stages,
        # reference OpWorkflowModelWriter persists RFF results into
        # op-model.json (OpWorkflowModelWriter.scala:75-120)
        "rawFeatureFilterResults": rff.to_json() if rff is not None
        else None,
        "blacklistedFeatureNames": list(
            getattr(model, "blacklisted_feature_names", ())),
    }
    from ..runtime.faults import maybe_inject
    tmp = f"{path}.tmp-save{os.getpid()}"
    if os.path.isdir(tmp):
        import shutil
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    json_path = os.path.join(tmp, MODEL_JSON)
    with open(json_path, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.flush()
        os.fsync(fh.fileno())
    _save_drift_fingerprints(model, tmp)
    # deterministic crash site for the atomicity tests: a kill here
    # leaves a staged dir + an untouched (or previous) target
    maybe_inject("workflow", "save", "save")
    np.savez(os.path.join(tmp, ARRAYS_NPZ),
             **{k: v for k, v in arrays.items()})
    _fsync_file(os.path.join(tmp, ARRAYS_NPZ))
    # canonical plan fingerprint sidecar (analysis/audit.py): the
    # lowered scoring program's IR identity, recorded at save time and
    # verified on load (plan_fingerprint_drift). Written AFTER the
    # identity files so the content-keyed audit cache can key on them;
    # best-effort inside the hook — it never breaks a save.
    _record_plan_fingerprint(model, tmp)
    # AOT artifact export (artifacts/, docs/aot_artifacts.md): every
    # bucket program compiled + serialized into the staging dir, so
    # the artifact store rides the same atomic swap as the model. The
    # fingerprint sidecar just written above is its identity key.
    _export_plan_artifacts(model, tmp)
    if os.path.isdir(path):
        # swap: rename can't replace a non-empty dir, so move the old
        # model aside first; it is removed only after the new one is in
        # place (worst crash outcome: old model at <path>.old-save*)
        old = f"{path}.old-save{os.getpid()}"
        if os.path.isdir(old):
            import shutil
            shutil.rmtree(old)
        os.rename(path, old)
        os.rename(tmp, path)
        import shutil
        shutil.rmtree(old)
    else:
        os.rename(tmp, path)
    # the drift sentinel (serving/sentinel.py) resolves fingerprints
    # through the model dir
    model.model_dir = path


def _record_plan_fingerprint(model, staging_dir: str) -> None:
    """Satellite of the plan auditor (analysis/audit.py): compute the
    canonical IR fingerprint of the model's scoring program and stage
    it as ``plan-fingerprint.json``. Best-effort and env-gated
    (``TX_PLAN_FINGERPRINT=off`` disables) — a model whose plan cannot
    lower saves without a fingerprint, loudly, never fails."""
    try:
        from ..analysis.audit import record_plan_fingerprint
        record_plan_fingerprint(model, staging_dir)
    except Exception as e:   # never let the auditor break a save
        import logging
        logging.getLogger(__name__).warning(
            "plan fingerprint not recorded (%s: %s); the saved model "
            "carries no AOT artifact identity", type(e).__name__, e)


def _export_plan_artifacts(model, staging_dir: str) -> None:
    """Satellite of the artifact store (artifacts/export.py): AOT-
    compile + serialize the model's bucket programs into the staging
    dir. Best-effort and env-gated (``TX_AOT_EXPORT=off`` disables) —
    a model whose programs cannot export saves without artifacts,
    loudly, and live-compiles at serve time exactly as before."""
    try:
        from ..artifacts.export import export_model_artifacts
        export_model_artifacts(model, staging_dir)
    except Exception as e:   # never let the exporter break a save
        import logging
        logging.getLogger(__name__).warning(
            "AOT artifacts not exported (%s: %s); the saved model "
            "will live-compile at serve boot", type(e).__name__, e)


def _save_drift_fingerprints(model, staging_dir: str) -> None:
    """Serialize the training-time per-feature distributions into the
    model dir (``drift-fingerprints.json``) so the serve-time drift
    sentinel (serving/sentinel.py) can compare scored traffic against
    training without the training data. Best-effort: a model without a
    train dataset (e.g. one loaded from an older save) simply carries
    no fingerprints, and the sentinel reports itself unavailable."""
    train_ds = getattr(model, "train_dataset", None)
    if train_ds is None:
        return
    from ..serving.sentinel import compute_fingerprints, save_fingerprints
    try:
        fps = compute_fingerprints(model.raw_features(), train_ds)
        if fps:
            save_fingerprints(
                fps, staging_dir,
                trained_at=getattr(model, "trained_generation", 0))
    except Exception as e:   # never let fingerprinting break a save
        import logging
        logging.getLogger(__name__).warning(
            "drift fingerprints not saved (%s: %s); the saved model "
            "will serve without the drift sentinel", type(e).__name__, e)


def _referenced_array_keys(node: Any) -> List[str]:
    """Every ``{"$array": key}`` reference in a model document — the
    npz sidecar must supply ALL of them or the dir is partial."""
    keys: List[str] = []
    if isinstance(node, dict):
        if "$array" in node and isinstance(node["$array"], str):
            keys.append(node["$array"])
        else:
            for v in node.values():
                keys.extend(_referenced_array_keys(v))
    elif isinstance(node, list):
        for v in node:
            keys.extend(_referenced_array_keys(v))
    return keys


def load_model(path: str):
    """Load a fitted WorkflowModel from ``path``
    (reference OpWorkflowModelReader / OpWorkflow.loadModel).

    Rejects partial/corrupt model directories (a crash mid-save before
    r4's atomic writer, or a stray staging dir) with a clear error
    instead of failing deep inside stage deserialization."""
    from .workflow import WorkflowModel
    json_path = os.path.join(path, MODEL_JSON)
    if not os.path.isdir(path) or not os.path.exists(json_path):
        raise ValueError(
            f"{path!r} is not a saved model directory (no {MODEL_JSON})"
            + (" — it looks like an interrupted save; re-save the "
               "model" if "tmp-save" in os.path.basename(path)
               or os.path.isdir(path) else ""))
    with open(json_path) as fh:
        try:
            doc = json.load(fh)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"model at {path} has a corrupt/truncated {MODEL_JSON} "
                f"({e}) — likely an interrupted save; re-save the "
                f"model") from e
    fmt = doc.get("formatVersion", 1)
    if fmt > MODEL_FORMAT_VERSION:
        raise ValueError(
            f"model at {path} uses format {fmt}; this build reads up "
            f"to {MODEL_FORMAT_VERSION} — load with a newer build")
    npz_path = os.path.join(path, ARRAYS_NPZ)
    arrays: Dict[str, np.ndarray] = {}
    needed = set(_referenced_array_keys(doc.get("stages", [])))
    if os.path.exists(npz_path):
        with np.load(npz_path, allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    missing = sorted(needed - set(arrays))
    if missing:
        raise ValueError(
            f"model at {path} is partial: {MODEL_JSON} references "
            f"{len(needed)} arrays but "
            f"{ARRAYS_NPZ if os.path.exists(npz_path) else 'the missing ' + ARRAYS_NPZ} "
            f"lacks {len(missing)} of them (e.g. {missing[0]!r}) — "
            f"an interrupted save; re-save the model")

    stages: Dict[str, PipelineStage] = {}
    for sd in doc["stages"]:
        stages[sd["uid"]] = stage_from_json(sd, arrays)

    features: Dict[str, Feature] = {}
    for fd in doc["features"]:
        parents = tuple(features[u] for u in fd["parentUids"])
        stage = stages.get(fd["originStageUid"]) \
            if fd["originStageUid"] else None
        f = Feature(name=fd["name"],
                    ftype=feature_type_by_name(fd["typeName"]),
                    is_response=fd["isResponse"], origin_stage=stage,
                    parents=parents, uid=fd["uid"])
        features[f.uid] = f
        if stage is not None:
            stage.input_features = parents
            stage._output_feature = f
    result = tuple(features[u] for u in doc["resultFeatureUids"])
    rff = None
    if doc.get("rawFeatureFilterResults"):
        from ..checkers.raw_feature_filter import RawFeatureFilterResults
        rff = RawFeatureFilterResults.from_json(
            doc["rawFeatureFilterResults"])
    model = WorkflowModel(
        result_features=result, raw_feature_filter_results=rff,
        blacklisted_feature_names=doc.get("blacklistedFeatureNames", ()))
    # remember where this model lives: the drift sentinel loads its
    # training fingerprints (drift-fingerprints.json) from here
    model.model_dir = path
    # verify the save-time canonical plan fingerprint against THIS
    # environment's lowering (analysis/audit.py): a mismatch means the
    # compiled scoring program changed since save (kernel edit, jax
    # upgrade, platform move) — counted as plan_fingerprint_drift
    # telemetry + a loud warning, never an error
    try:
        from ..analysis.audit import verify_plan_fingerprint
        verify_plan_fingerprint(model, path)
    except Exception:  # the auditor never breaks a load
        pass
    return model
