"""OpParams + WorkflowRunner: production batch driver.

TPU-native port of the reference run scaffold
(core/src/main/scala/com/salesforce/op/{OpWorkflowRunner.scala:70,163-295,
358,379, OpApp.scala:49,178} and features/.../OpParams.scala:81):

- :class:`OpParams` — run configuration (per-stage param overrides by
  class name or uid, reader limits, model/write/metrics locations,
  custom tags), loadable from JSON or YAML.
- :class:`WorkflowRunner` — executes one of the five run types:
  ``train`` (fit + save model + summary), ``score`` (load + batch
  score + save), ``features`` (materialize up to a feature),
  ``evaluate`` (score + metrics), ``streaming_score`` (micro-batch
  scoring over a record-batch stream).
"""
from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional

import numpy as np

_log = logging.getLogger(__name__)

__all__ = ["OpParams", "WorkflowRunner", "RunType", "RunResult"]


class RunType:
    """(reference OpWorkflowRunType, OpWorkflowRunner.scala:358)"""
    TRAIN = "train"
    SCORE = "score"
    FEATURES = "features"
    EVALUATE = "evaluate"
    STREAMING_SCORE = "streaming_score"
    ALL = (TRAIN, SCORE, FEATURES, EVALUATE, STREAMING_SCORE)


@dataclass
class OpParams:
    """(reference OpParams.scala:81-100)"""
    #: per-stage ctor-param overrides keyed by stage class name or uid
    stage_params: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    reader_params: Dict[str, Any] = field(default_factory=dict)
    model_location: Optional[str] = None
    write_location: Optional[str] = None
    metrics_location: Optional[str] = None
    batch_size: int = 1000
    #: "json" | "avro" — format for saved scores (reference writes Avro)
    score_format: str = "json"
    custom_params: Dict[str, Any] = field(default_factory=dict)
    custom_tag_name: Optional[str] = None
    custom_tag_value: Optional[str] = None
    collect_metrics: bool = False

    def to_json(self) -> dict:
        return {"stageParams": self.stage_params,
                "readerParams": self.reader_params,
                "modelLocation": self.model_location,
                "writeLocation": self.write_location,
                "metricsLocation": self.metrics_location,
                "batchSize": self.batch_size,
                "scoreFormat": self.score_format,
                "customParams": self.custom_params,
                "customTagName": self.custom_tag_name,
                "customTagValue": self.custom_tag_value,
                "collectMetrics": self.collect_metrics}

    @staticmethod
    def from_dict(d: dict) -> "OpParams":
        return OpParams(
            stage_params=d.get("stageParams", {}),
            reader_params=d.get("readerParams", {}),
            model_location=d.get("modelLocation"),
            write_location=d.get("writeLocation"),
            metrics_location=d.get("metricsLocation"),
            batch_size=d.get("batchSize", 1000),
            score_format=d.get("scoreFormat", "json"),
            custom_params=d.get("customParams", {}),
            custom_tag_name=d.get("customTagName"),
            custom_tag_value=d.get("customTagValue"),
            collect_metrics=d.get("collectMetrics", False))

    @staticmethod
    def load(path: str) -> "OpParams":
        """JSON or YAML file (reference OpParams JSON/YAML loading)."""
        with open(path) as fh:
            text = fh.read()
        try:
            return OpParams.from_dict(json.loads(text))
        except json.JSONDecodeError:
            import yaml
            return OpParams.from_dict(yaml.safe_load(text))


@dataclass
class RunResult:
    """(reference OpWorkflowRunnerResult classes)"""
    run_type: str
    model_location: Optional[str] = None
    write_location: Optional[str] = None
    metrics: Optional[dict] = None
    summary: Optional[str] = None
    n_rows: Optional[int] = None
    seconds: float = 0.0
    #: streaming_score: micro-batches recorded + skipped after a
    #: scoring failure (None for non-streaming run types)
    skipped_batches: Optional[int] = None

    def to_json(self) -> dict:
        out = {"runType": self.run_type,
               "modelLocation": self.model_location,
               "writeLocation": self.write_location,
               "metrics": self.metrics, "nRows": self.n_rows,
               "seconds": self.seconds}
        if self.skipped_batches is not None:
            out["skippedBatches"] = self.skipped_batches
        return out


def _apply_stage_params(workflow, params: OpParams) -> None:
    """Override stage ctor params by class name or uid before fitting
    (reference OpWorkflow.setStageParameters:166)."""
    if not params.stage_params:
        return
    for stage in workflow.stages():
        for key in (type(stage).__name__, stage.uid):
            overrides = params.stage_params.get(key)
            if overrides:
                for k, v in overrides.items():
                    if not hasattr(stage, k):
                        raise ValueError(
                            f"Stage {key} has no param {k!r}")
                    setattr(stage, k, v)
                    if hasattr(stage, "_ctor_args") \
                            and k in stage._ctor_args:
                        stage._ctor_args[k] = v


class WorkflowRunner:
    """(reference OpWorkflowRunner.scala:70)"""

    def __init__(self, workflow=None, train_reader=None, score_reader=None,
                 evaluator=None, features: Optional[List] = None):
        self.workflow = workflow
        self.train_reader = train_reader
        self.score_reader = score_reader
        self.evaluator = evaluator
        self.features = features or []

    # -- dispatch (reference run:296) --------------------------------------
    def run(self, run_type: str, params: Optional[OpParams] = None
            ) -> RunResult:
        params = params or OpParams()
        t0 = time.perf_counter()
        if run_type == RunType.TRAIN:
            result = self._train(params)
        elif run_type == RunType.SCORE:
            result = self._score(params)
        elif run_type == RunType.FEATURES:
            result = self._features(params)
        elif run_type == RunType.EVALUATE:
            result = self._evaluate(params)
        elif run_type == RunType.STREAMING_SCORE:
            result = self._streaming_score_reader(params)
        else:
            raise ValueError(f"Unknown run type {run_type!r}; "
                             f"one of {RunType.ALL}")
        result.seconds = round(time.perf_counter() - t0, 3)
        self._write_metrics(result, params)
        return result

    # -- run types (reference :163-295) ------------------------------------
    def _train(self, params: OpParams) -> RunResult:
        if self.workflow is None:
            raise ValueError("train requires a workflow")
        if self.train_reader is not None:
            self.workflow.set_reader(self.train_reader)
        _apply_stage_params(self.workflow, params)
        model = self.workflow.train()
        summary = model.summary_pretty()
        if params.model_location:
            model.save(params.model_location)
            with open(os.path.join(params.model_location,
                                   "summary.txt"), "w") as fh:
                fh.write(summary)
        self.model = model
        return RunResult(run_type=RunType.TRAIN,
                         model_location=params.model_location,
                         summary=summary)

    def _load_model(self, params: OpParams):
        model = getattr(self, "model", None)
        if model is not None:
            return model
        if not params.model_location:
            raise ValueError("model_location required to load a model")
        from .persistence import load_model
        return load_model(params.model_location)

    def _score(self, params: OpParams) -> RunResult:
        if self.score_reader is None:
            raise ValueError("score requires a score_reader")
        model = self._load_model(params)
        scored = model.score(self.score_reader)
        n = scored.n_rows
        write = None
        if params.write_location:
            write = self._write_scores(scored, model, params.write_location,
                                       params.score_format)
        return RunResult(run_type=RunType.SCORE, write_location=write,
                         model_location=params.model_location, n_rows=n)

    def _features(self, params: OpParams) -> RunResult:
        model = self._load_model(params)
        if not self.features:
            raise ValueError("features run type requires features=[...]")
        ds = model.compute_data_up_to(self.features[0],
                                      self.score_reader
                                      or self.train_reader)
        return RunResult(run_type=RunType.FEATURES, n_rows=ds.n_rows)

    def _evaluate(self, params: OpParams) -> RunResult:
        if self.evaluator is None:
            raise ValueError("evaluate requires an evaluator")
        model = self._load_model(params)
        _, metrics = model.score_and_evaluate(
            self.score_reader or self.train_reader, self.evaluator)
        return RunResult(run_type=RunType.EVALUATE,
                         metrics=metrics.to_json())

    def _streaming_score_reader(self, params: OpParams) -> RunResult:
        """run(STREAMING_SCORE): drain the StreamingReader set as
        score_reader (reference streamingScore:232-270 drains the
        DStream), optionally appending scored batches as JSON lines."""
        from ..readers.streaming import StreamingReader
        if not isinstance(self.score_reader, StreamingReader):
            raise ValueError(
                "streaming_score requires score_reader to be a "
                "StreamingReader (or call streaming_score(batches, "
                "params) directly)")
        n = 0
        out_path = None
        sink = None
        if params.write_location:
            os.makedirs(params.write_location, exist_ok=True)
            out_path = os.path.join(params.write_location,
                                    "scores.jsonl")
            sink = open(out_path, "w")
        try:
            for batch in self.streaming_score(self.score_reader.stream(),
                                              params):
                n += len(batch)
                if sink is not None:
                    for row in batch:
                        sink.write(json.dumps(row, default=float) + "\n")
        finally:
            if sink is not None:
                sink.close()
        stats = getattr(self, "last_stream_stats", {}) or {}
        return RunResult(run_type=RunType.STREAMING_SCORE,
                         model_location=params.model_location,
                         write_location=out_path, n_rows=n,
                         skipped_batches=stats.get("skipped_batches", 0))

    def streaming_score(self, batches: Iterable[Iterable[dict]],
                        params: Optional[OpParams] = None,
                        stop_on_error: bool = False,
                        guardrails: Any = False
                        ) -> Iterator[List[dict]]:
        """Micro-batch scoring over a stream of record batches
        (reference streamingScore:232 over DStream micro-batches). Uses
        the row-level local scoring path so per-batch latency stays flat.

        Per-batch failures are ISOLATED by default: a failing batch is
        recorded (telemetry event ``stream_batch_skipped`` + counter
        ``stream_batches_skipped``) and skipped, and the stream
        continues — one poisoned micro-batch must not kill a long-lived
        stream. The running tally lands on ``self.last_stream_stats``
        (``run(STREAMING_SCORE)`` surfaces it as
        ``RunResult.skipped_batches``). ``stop_on_error=True`` restores
        the reference's stop-the-stream semantics
        (OpWorkflowRunner.scala:313-320). ``KillPoint``/interrupts are
        BaseExceptions and always propagate.

        ``guardrails`` enables the serving guardrails for every batch
        (docs/serving_guardrails.md): True for defaults or a dict of
        ``ScoringPlan.with_guardrails`` kwargs — quarantined rows then
        carry ``"_guard"`` reasons instead of poisoning the batch."""
        from ..runtime import telemetry as _telemetry
        params = params or OpParams()
        model = self._load_model(params)
        from ..local.scoring import ScoreFunction
        fn = ScoreFunction(model, guardrails=guardrails)
        self.last_stream_stats = {"batches": 0, "skipped_batches": 0,
                                  "rows": 0}
        for i, batch in enumerate(batches):
            self.last_stream_stats["batches"] += 1
            try:
                scored = fn.score_batch(list(batch))
            except Exception as e:
                if stop_on_error:
                    _log.error("streaming batch %d failed; stopping the "
                               "stream (reference stop-on-error, "
                               "OpWorkflowRunner.scala:313-320)", i)
                    raise
                # recorded + skipped, never silent (the TX-R02 contract)
                self.last_stream_stats["skipped_batches"] += 1
                _telemetry.count("stream_batches_skipped")
                _telemetry.event("stream_batch_skipped", batch=i,
                                 error=f"{type(e).__name__}: {e}")
                _log.warning("streaming batch %d failed; recorded and "
                             "skipped", i, exc_info=True)
                continue
            self.last_stream_stats["rows"] += len(scored)
            # the yield sits OUTSIDE the try: an exception thrown INTO
            # the suspended generator must propagate as the consumer's
            # error, not be misattributed to batch scoring
            yield scored

    # -- output ------------------------------------------------------------
    @staticmethod
    def _jsonable(v):
        """Boxed feature value -> JSON-representable value (arrays and
        tuples to lists, sets to sorted lists, numpy scalars unboxed);
        recurses through maps and collections."""
        if isinstance(v, np.ndarray):
            v = v.tolist()
        if isinstance(v, (np.floating, np.integer)):
            return v.item()
        if isinstance(v, dict):
            return {str(k): WorkflowRunner._jsonable(x)
                    for k, x in v.items()}
        if isinstance(v, (set, frozenset)):
            return sorted(WorkflowRunner._jsonable(x) for x in v)
        if isinstance(v, (list, tuple)):
            return [WorkflowRunner._jsonable(x) for x in v]
        return v

    def _write_scores(self, scored, model, location: str,
                      fmt: str = "json") -> str:
        """Persist result-feature rows; fmt "json" or "avro" (the
        reference saves scores as Avro, RichDataset.saveAvro;
        OpParams.score_format selects). Map/collection values stay
        structured in JSON and flatten to JSON strings for the
        flat-record Avro schema."""
        if fmt not in ("json", "avro"):
            raise ValueError(f"score_format must be json|avro, got {fmt!r}")
        os.makedirs(location, exist_ok=True)
        names = [f.name for f in model.result_features]
        rows = []
        for i in range(scored.n_rows):
            row = {}
            for name in names:
                col = scored[name]
                boxed = col.boxed(i)
                v = self._jsonable(
                    boxed.value if hasattr(boxed, "value") else boxed)
                if fmt == "avro" and isinstance(v, (dict, list)):
                    v = json.dumps(v)
                row[name] = v
            rows.append(row)
        if fmt == "avro":
            from ..utils.avro_io import write_avro
            out = os.path.join(location, "scores.avro")
            write_avro(out, rows)
            return out
        out = os.path.join(location, "scores.json")
        with open(out, "w") as fh:
            json.dump(rows, fh)
        return out

    def _write_metrics(self, result: RunResult, params: OpParams) -> None:
        if params.metrics_location:
            os.makedirs(params.metrics_location, exist_ok=True)
            with open(os.path.join(params.metrics_location,
                                   f"{result.run_type}_metrics.json"),
                      "w") as fh:
                json.dump(result.to_json(), fh)
