"""The workflow engine: fit a feature DAG, score with the fitted model.

TPU-native re-design of the reference workflow core
(core/src/main/scala/com/salesforce/op/{OpWorkflow.scala:332,
OpWorkflowModel.scala:253, OpWorkflowCore.scala:52} and the DAG executor
core/.../utils/stages/FitStagesUtil.scala:173-305). Differences from the
Spark design:

- Data is a columnar :class:`Dataset` (host numpy feeding XLA device
  arrays), not a Spark DataFrame; a "layer" of the DAG is executed as
  direct columnar kernels instead of one RDD map over row closures
  (FitStagesUtil.applyOpTransformations:96).
- Estimator -> fitted-model DAG rewiring uses
  ``Feature.copy_with_new_stages`` exactly like the reference
  (OpWorkflow.scala:347).
"""
from __future__ import annotations

import logging
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

_log = logging.getLogger(__name__)

from ..evaluators.base import EvaluationMetrics, Evaluator
from ..features.columns import Dataset, FeatureColumn
from ..features.feature import Feature, topo_layers
from ..features.generator import FeatureGeneratorStage
from ..stages.base import Estimator, PipelineStage, Transformer

__all__ = ["Workflow", "WorkflowModel"]


def _unique_raw_features(result_features: Sequence[Feature]) -> List[Feature]:
    uniq: Dict[str, Feature] = {}
    for rf in result_features:
        for f in rf.raw_features():
            uniq.setdefault(f.uid, f)
    return sorted(uniq.values(), key=lambda f: f.name)


def _generate_raw_data(raw_features: Sequence[Feature], data: Any,
                       require_responses: bool) -> Dataset:
    """Materialize raw feature columns from a Dataset or record iterable
    (reference generateRawData, OpWorkflow.scala:222 + readers'
    DataReader.generateDataFrame, readers/.../DataReader.scala:173).

    At score time (``require_responses=False``) absent response features
    become all-NaN columns so non-nullable label types don't block
    label-free scoring.
    """
    from ..readers.data_readers import DataReader
    if isinstance(data, DataReader):
        # (reference reader.generateDataFrame, Reader.scala:168)
        if require_responses:
            data = data.generate_dataset(raw_features)
        else:
            # label-free scoring: a response column the data can't
            # produce becomes all-NaN instead of failing extraction
            predictors = [f for f in raw_features if not f.is_response]
            ds0 = data.generate_dataset(predictors)
            cols0 = {f.name: ds0[f.name] for f in predictors}
            n0 = ds0.n_rows
            for f in raw_features:
                if not f.is_response:
                    continue
                try:
                    cols0[f.name] = data.generate_dataset([f])[f.name]
                except Exception as e:
                    _log.warning(
                        "response %r not extractable from score data "
                        "(%s); substituting an all-NaN column", f.name, e)
                    cols0[f.name] = FeatureColumn(
                        ftype=f.ftype,
                        data=np.full(n0, np.nan, dtype=np.float64))
            data = Dataset(cols0)
    if isinstance(data, Dataset):
        n = data.n_rows
        cols: Dict[str, FeatureColumn] = {}
        for f in raw_features:
            if f.name in data:
                cols[f.name] = data[f.name]
            elif f.is_response and not require_responses:
                cols[f.name] = FeatureColumn(
                    ftype=f.ftype, data=np.full(n, np.nan, dtype=np.float64))
            else:
                raise KeyError(
                    f"Raw feature {f.name!r} not present in input dataset")
        return Dataset(cols)

    records = list(data)
    cols = {}
    for f in raw_features:
        gen = f.origin_stage
        if not isinstance(gen, FeatureGeneratorStage):
            raise TypeError(
                f"Raw feature {f.name!r} has no generator stage")
        if f.is_response and not require_responses:
            # user extract fns may KeyError/None on label-free score data
            def safe(r, fn=gen.extract_fn):
                try:
                    return fn(r)
                except Exception:
                    return None
            vals = [safe(r) for r in records]
            if all(v is None for v in vals):
                cols[f.name] = FeatureColumn(
                    ftype=f.ftype,
                    data=np.full(len(records), np.nan, dtype=np.float64))
                continue
        cols[f.name] = gen.extract_column(records)
    return Dataset(cols)


def _fit_and_transform_layers(
        layers: List[List[PipelineStage]], ds: Dataset, fit: bool,
        listener=None, prefitted: Optional[Dict[str, PipelineStage]] = None
        ) -> Tuple[Dataset, Dict[str, PipelineStage]]:
    """Layer-by-layer DAG execution (reference
    FitStagesUtil.fitAndTransformDAG:213 / fitAndTransformLayer:254):
    estimators in a layer are fitted then their models applied; plain
    transformers are applied directly. ``prefitted`` supplies models
    already fitted on THIS dataset (the workflow-CV pre-pass) so they
    are not fitted twice."""
    import time as _time
    fitted: Dict[str, PipelineStage] = {}
    if listener is not None:
        # per-stage compile/execute split (utils/compile_time.py);
        # no-op zeros on a jax without the monitoring API
        from ..utils import compile_time
        compile_time.install()

    def timed(stage, phase, fn):
        t0 = _time.perf_counter()
        c0 = compile_time.compile_seconds() if listener is not None else 0.0
        result = fn()
        if listener is not None:
            listener.on_stage_completed(
                stage, phase, _time.perf_counter() - t0, ds.n_rows,
                compile_seconds=compile_time.compile_seconds() - c0)
        return result

    for layer in layers:
        for stage in layer:
            if isinstance(stage, FeatureGeneratorStage):
                continue  # raw features are already materialized
            if isinstance(stage, Estimator):
                if not fit:
                    raise RuntimeError(
                        f"Unfitted estimator {stage!r} in scoring DAG — "
                        "train the workflow first")
                model = (prefitted or {}).get(stage.uid)
                if model is None:
                    model = timed(stage, "fit", lambda: stage.fit(ds))
                fitted[stage.uid] = model
                out = stage.get_output()
                ds = ds.with_column(
                    out.name, timed(
                        stage, "transform",
                        lambda: model.transform_columns(  # tx-lint: disable=TX-J09 (TX_PREPARE=host escape hatch)
                            [ds[f.name] for f in model.input_features])))
            elif isinstance(stage, Transformer):
                ds = timed(stage, "transform",
                           lambda: stage.transform_dataset(ds))  # tx-lint: disable=TX-J09 (TX_PREPARE=host escape hatch)
            else:
                raise TypeError(f"Cannot execute stage {stage!r}")
    return ds, fitted


def check_serializable(result_features: Sequence[Feature]) -> List[str]:
    """Pre-train serializability audit (reference
    OpWorkflow.checkSerializable:265 + ClosureUtils): every feature
    extract fn and stage ctor arg must be importable (module:qualname)
    for the saved model to round-trip; lambdas/closures survive
    in-process scoring but are DROPPED by persistence. Returns the list
    of problems (empty = fully serializable)."""
    problems: List[str] = []

    def fn_importable(fn) -> bool:
        # shared with the persistence encoder so the audit warns about
        # EXACTLY what save would drop (incl. __main__-script functions
        # whose module another process cannot re-import)
        from .persistence import resolve_importable_fn
        return resolve_importable_fn(fn) is not None

    for layer in topo_layers(result_features):
        for stage in layer:
            if isinstance(stage, FeatureGeneratorStage):
                if not fn_importable(stage.extract_fn):
                    problems.append(
                        f"raw feature {stage.get_output().name!r}: "
                        f"extract fn is a lambda/closure (not importable)")
                continue
            for k, v in getattr(stage, "_ctor_args", {}).items():
                if callable(v) and not isinstance(v, type) \
                        and not fn_importable(v):
                    problems.append(
                        f"stage {type(stage).__name__}({stage.uid}): "
                        f"ctor arg {k!r} is a lambda/closure "
                        f"(not importable)")
    return problems


def _validate_distinct_uids(result_features: Sequence[Feature]) -> None:
    """Every stage in the DAG must have a unique uid — duplicate uids
    silently alias fitted models during DAG rewiring (reference
    OpWorkflow.scala:305 validation)."""
    seen: Dict[str, PipelineStage] = {}
    for layer in topo_layers(result_features):
        for stage in layer:
            other = seen.get(stage.uid)
            if other is not None and other is not stage:
                raise ValueError(
                    f"Duplicate stage uid {stage.uid!r}: "
                    f"{type(other).__name__} and {type(stage).__name__}. "
                    f"Each stage instance needs its own uid — don't reuse "
                    f"one stage object with different inputs")
            seen[stage.uid] = stage


def _transform_with_fitted(layers: List[List[PipelineStage]],
                           fitted: Dict[str, PipelineStage],
                           ds: Dataset) -> Dataset:
    """Apply already-fitted stages to new rows (the validation side of a
    workflow-CV fold; reference FittedDAG.transformers application,
    FitStagesUtil.scala:254-292)."""
    for layer in layers:
        for stage in layer:
            if isinstance(stage, FeatureGeneratorStage):
                continue
            if isinstance(stage, Estimator):
                model = fitted[stage.uid]
                out = stage.get_output()
                ds = ds.with_column(out.name, model.transform_columns(  # tx-lint: disable=TX-J09 (per-fold refit segments stay host-side)
                    [ds[f.name] for f in model.input_features]))
            else:
                ds = stage.transform_dataset(ds)  # tx-lint: disable=TX-J09 (per-fold refit segments stay host-side)
    return ds


def cut_dag(result_features: Sequence[Feature]):
    """Split the DAG around the ModelSelector for leakage-free
    workflow-level CV (reference FitStagesUtil.cutDAG:305).

    Returns (selector, during_layers) where ``during_layers`` are the
    selector-ancestor layers from the FIRST stage whose inputs mix a
    response with predictors (e.g. SanityChecker) onward — exactly the
    stages whose full-data fit would leak validation-fold label
    information into model selection. Empty when there is no selector or
    no label-consuming ancestor. Raises on >1 selector (reference
    "at most 1 Model Selector").
    """
    from ..selector.selector import ModelSelector
    layers = topo_layers(result_features)
    selectors = [s for layer in layers for s in layer
                 if isinstance(s, ModelSelector)]
    if len(selectors) > 1:
        raise ValueError(
            f"Workflow can contain at most 1 ModelSelector for "
            f"workflow-level CV; found {len(selectors)}")
    if not selectors:
        return None, []
    ms = selectors[0]
    anc_layers = topo_layers(list(ms.input_features))
    first = None
    for i, layer in enumerate(anc_layers):
        for s in layer:
            if isinstance(s, FeatureGeneratorStage):
                continue
            ins = getattr(s, "input_features", ())
            if (any(f.is_response for f in ins)
                    and any(not f.is_response for f in ins)):
                first = i
                break
        if first is not None:
            break
    if first is None:
        return ms, []
    during = [[s for s in layer if not isinstance(s, FeatureGeneratorStage)]
              for layer in anc_layers[first:]]
    return ms, [l for l in during if l]


class Workflow:
    """Declare result features + input data, then ``train()``
    (reference OpWorkflow.scala:59)."""

    def __init__(self):
        self.result_features: Tuple[Feature, ...] = ()
        self._input_data: Any = None
        self._raw_feature_filter = None
        self._rff_score_data: Any = None
        self._workflow_cv = False
        #: raw features removed by the RawFeatureFilter (reference
        #: blacklistedFeatures on OpWorkflow)
        self.blacklisted_features: Tuple[Feature, ...] = ()
        #: RawFeatureFilterResults after train() (reference
        #: getRawFeatureFilterResults)
        self.raw_feature_filter_results = None

    # -- configuration -----------------------------------------------------
    def set_result_features(self, *features: Feature) -> "Workflow":
        """(reference setResultFeatures:85; stages are derived from the
        feature DAG via topological sort, setStagesDAG:195)"""
        if not features:
            raise ValueError("At least one result feature required")
        self.result_features = tuple(features)
        return self

    def set_input_dataset(self, ds: Dataset) -> "Workflow":
        """(reference setInputDataset:136)"""
        self._input_data = ds
        return self

    def set_input_records(self, records: Iterable[Any]) -> "Workflow":
        """Row records (dicts/objects); raw features are extracted with
        their generator stages (reference setInputRDD)."""
        self._input_data = list(records)
        return self

    def set_reader(self, reader) -> "Workflow":
        """A DataReader supplies (and possibly aggregates) the raw data
        (reference setReader, OpWorkflowCore.scala:121)."""
        self._input_data = reader
        return self

    def with_listener(self, listener) -> "Workflow":
        """Attach a WorkflowListener collecting per-stage metrics
        (reference OpSparkListener wiring, OpWorkflowRunner.scala:326)."""
        self._listener = listener
        return self

    def with_raw_feature_filter(self, rff,
                                score_data: Any = None) -> "Workflow":
        """Enable pre-DAG raw-feature exclusion during ``train()``
        (reference withRawFeatureFilter on OpWorkflow). ``score_data``
        optionally supplies scoring-time data for distribution-shift
        checks."""
        self._raw_feature_filter = rff
        self._rff_score_data = score_data
        return self

    def with_workflow_cv(self) -> "Workflow":
        """Leakage-free workflow-level CV (reference withWorkflowCV,
        OpWorkflowCore.scala:109 + OpWorkflow.scala:388-440): during
        model selection, every label-consuming ancestor stage of the
        ModelSelector (e.g. SanityChecker) is REFIT inside each CV fold
        on that fold's training rows only, so validation metrics carry no
        fold leakage. The winner is then refit on the full data."""
        self._workflow_cv = True
        return self

    # -- introspection -----------------------------------------------------
    def raw_features(self) -> List[Feature]:
        return _unique_raw_features(self.result_features)

    def stages(self) -> List[PipelineStage]:
        return [s for layer in topo_layers(self.result_features)
                for s in layer if not isinstance(s, FeatureGeneratorStage)]

    # -- training ----------------------------------------------------------
    def train(self, validate: str = "warn",
              resume_from: Optional[str] = None) -> "WorkflowModel":
        """Fit all estimators layer-by-layer and return the fitted model
        (reference OpWorkflow.train:332 / fitStages:368).

        ``validate`` runs the pre-flight static analyzer (lint/) over
        the feature DAG BEFORE any data is read, any stage traced or any
        device buffer allocated — the compile-time safety pillar of the
        reference, restored as a millisecond graph walk:

        - ``"strict"``: raise :class:`~..lint.LintError` on any
          error-severity finding (leakage path, cycle, type-contract
          violation, duplicate uid, ...)
        - ``"warn"`` (default): log findings and continue
        - ``"off"``: skip the pre-flight entirely

        ``resume_from`` points the workflow's ModelSelector at a search
        checkpoint directory (docs/resilience.md): completed (family,
        candidates, rung) evaluations journaled by a previous —
        possibly killed — ``train()`` with the same search fingerprint
        replay from disk, and only the missing work is dispatched. The
        resumed search picks the bitwise-identical winner. The same
        directory is also written to, so repeatedly retrying
        ``train(resume_from=d)`` after crashes converges. Equivalent to
        constructing ``ModelSelector(checkpoint_dir=...)``.
        """
        if validate not in ("strict", "warn", "off"):
            raise ValueError(
                f"validate must be 'strict', 'warn' or 'off', "
                f"got {validate!r}")
        if not self.result_features:
            raise ValueError("No result features set")
        if self._input_data is None:
            raise ValueError("No input data set")
        if resume_from is not None:
            from ..selector.selector import ModelSelector
            selectors = [s for s in self.stages()
                         if isinstance(s, ModelSelector)]
            if not selectors:
                raise ValueError(
                    "resume_from requires a ModelSelector in the "
                    "workflow DAG — there is no search to resume")
            for s in selectors:
                s.checkpoint_dir = resume_from
        if validate != "off":
            from ..lint import ERROR, LintError, lint_workflow
            findings = lint_workflow(self)
            errors = [f for f in findings if f.severity == ERROR]
            if validate == "strict" and errors:
                raise LintError(errors)
            for f in findings:
                _log.warning("pre-flight lint: %s", f)
        result_features = self.result_features
        self.blacklisted_features = ()
        self.raw_feature_filter_results = None
        raw = self.raw_features()
        ds = _generate_raw_data(raw, self._input_data,
                                require_responses=True)
        if self._raw_feature_filter is not None:
            # (reference generateRawData -> RawFeatureFilter
            #  .generateFilteredRaw, OpWorkflow.scala:222)
            from ..checkers import rewire_without
            score_ds = None
            if self._rff_score_data is not None:
                score_ds = _generate_raw_data(
                    raw, self._rff_score_data, require_responses=False)
            responses = [f for f in raw if f.is_response]
            label = None
            if len(responses) == 1 and responses[0].name in ds \
                    and ds[responses[0].name].kind == "numeric":
                # non-numeric labels (e.g. string classes indexed
                # in-DAG) skip the null-label correlation check
                label = np.asarray(ds[responses[0].name].data,
                                   dtype=np.float64)
            results = self._raw_feature_filter.compute_exclusions(
                raw, ds, score_ds, label=label)
            self.raw_feature_filter_results = results
            if results.excluded_names:
                result_features, removed = rewire_without(
                    result_features, results.excluded_names)
                self.blacklisted_features = tuple(removed)
        _validate_distinct_uids(result_features)
        for problem in check_serializable(result_features):
            _log.warning("serializability: %s — model save/load will "
                         "drop it (reference checkSerializable, "
                         "OpWorkflow.scala:265)", problem)
        prefitted = None
        if self._workflow_cv:
            prefitted = self._find_best_with_workflow_cv(result_features, ds)
        listener = getattr(self, "_listener", None)
        # the train root span: prepare segments, family dispatches,
        # racing rungs and journal replays all nest under it
        # (docs/observability.md; off-by-default, TX_TRACE enables)
        from ..observability import trace as _trace
        with _trace.span("train", rows=ds.n_rows,
                         prepare=os.environ.get("TX_PREPARE", "plan")):
            train_ds, fitted = self._prepare(result_features, ds,
                                             listener, prefitted)
        result = tuple(f.copy_with_new_stages(fitted)
                       for f in result_features)
        if listener is not None:
            listener.on_application_end()
        return WorkflowModel(
            result_features=result, train_dataset=train_ds,
            raw_feature_filter_results=self.raw_feature_filter_results,
            blacklisted_feature_names=[f.name for f
                                       in self.blacklisted_features])

    def _prepare(self, result_features, ds, listener, prefitted):
        """Fit + transform the feature DAG over the training data.

        Default (``TX_PREPARE=plan``): the compiled prepare path
        (plans/prepare.py) — the fitted DAG executes through the SAME
        ``transform_arrays`` kernel library serving uses, fused into
        jitted segment programs, and the training matrices are born on
        device for the selector search (docs/prepare.md).
        ``TX_PREPARE=host`` is the escape hatch: the per-stage host
        ``transform_columns`` walk, exactly the pre-plan behavior. A
        plan that cannot be built degrades to the host path with the
        reason recorded (never silently)."""
        import os
        mode = os.environ.get("TX_PREPARE", "plan")
        if mode not in ("plan", "host"):
            raise ValueError(
                f"TX_PREPARE must be 'plan' or 'host', got {mode!r}")
        layers = topo_layers(result_features)
        if mode == "plan":
            from ..plans import PlanCompileError, PreparePlan
            plan = PreparePlan(result_features, listener=listener)
            try:
                train_ds, fitted = plan.execute(ds, prefitted=prefitted)
                #: introspection: coverage / fit placements / segment
                #: seconds of the most recent train (bench reads this)
                self.last_prepare_plan = plan
                return train_ds, fitted
            except PlanCompileError as e:
                from ..runtime import telemetry as _telemetry
                _telemetry.count("prepare_plan_fallbacks")
                _telemetry.event("prepare_plan_fallback",
                                 error=f"{type(e).__name__}: {e}")
                _log.warning(
                    "compiled prepare unavailable (%s); falling back to "
                    "the host transform_columns path", e)
        self.last_prepare_plan = None
        return _fit_and_transform_layers(layers, ds, fit=True,
                                         listener=listener,
                                         prefitted=prefitted)

    def _find_best_with_workflow_cv(self, result_features, ds
                                    ) -> Optional[Dict[str, PipelineStage]]:
        """Leakage-free model selection (reference OpWorkflow.scala:
        388-440 + OpValidator.applyDAG:228): refit the in-CV DAG segment
        per fold, validate candidates on per-fold matrices, preset the
        winner on the selector. Returns the models fitted by the
        pre-pass (selector ancestors OUTSIDE the in-CV segment, fitted
        on full data) so the final pass reuses instead of refitting
        them; the in-CV segment itself IS refit on full data there.

        The selector's splitter participates in the search exactly as in
        the reference: the holdout is reserved BEFORE folding
        (OpWorkflow.scala:372-376), the balancer/cutter plan is
        estimated once from the search labels
        (OpValidator.prepareStratification:203-226), and each fold's
        train AND validation rows are resampled with that plan after
        the in-CV DAG refit (OpValidator.applyDAG:250-252) — candidate
        ranking happens on balanced data, not just stratified folds."""
        selector, during = cut_dag(result_features)
        if selector is None or not during:
            return None  # nothing label-consuming feeds the selector
        during_uids = {s.uid for layer in during for s in layer}
        label_f, features_f = selector.input_features
        # 1. fit the selector's ancestors OUTSIDE the in-CV segment once
        #    on full data (reference nonCVTS DAG); non-ancestor stages
        #    and in-CV/selector consumers wait for the final pass
        anc_layers = [[s for s in layer
                       if not isinstance(s, FeatureGeneratorStage)
                       and s.uid not in during_uids]
                      for layer in topo_layers(list(selector.input_features))]
        pre, prefitted = _fit_and_transform_layers(
            [l for l in anc_layers if l], ds, fit=True)
        if label_f.name not in pre:
            _log.warning(
                "workflow-level CV skipped: label %r is produced inside "
                "the in-CV DAG segment", label_f.name)
            return prefitted
        # 2. reserve the holdout BEFORE folding so the search never sees
        #    it; the exact indices are preset on the selector so its
        #    final fit reuses THIS split rather than re-deriving one
        #    (structural agreement — no determinism convention to break)
        y_pre = np.asarray(pre[label_f.name].data, dtype=np.float64)
        splitter = selector.splitter
        reserved = None
        if splitter is not None:
            splitter.reset_plan()
            tr_idx, te_idx = splitter.split(y_pre)
            reserved = (tr_idx, te_idx)
            if len(te_idx):
                pre, y_pre = pre.take(tr_idx), y_pre[tr_idx]
            est = getattr(splitter, "estimate", None)
            if est is not None:   # one global resampling plan
                est(y_pre)
        # 3. per fold: refit the in-CV segment on the fold's train rows,
        #    transform its validation rows with those fitted stages,
        #    then apply the splitter's resampling plan to both
        validator = selector.validator
        folds = []
        for train_idx, val_idx in validator._splits(y_pre):
            tr_ds, fitted_cv = _fit_and_transform_layers(
                during, pre.take(train_idx), fit=True)
            val_ds = _transform_with_fitted(during, fitted_cv,
                                            pre.take(val_idx))
            fold = [
                np.asarray(tr_ds[features_f.name].data, dtype=np.float64),
                np.asarray(tr_ds[label_f.name].data, dtype=np.float64),
                np.asarray(val_ds[features_f.name].data, dtype=np.float64),
                np.asarray(val_ds[label_f.name].data, dtype=np.float64)]
            if splitter is not None:
                ridx = splitter.prepare(fold[1])
                vidx = splitter.prepare(fold[3])
                fold = [fold[0][ridx], fold[1][ridx],
                        fold[2][vidx], fold[3][vidx]]
            folds.append(tuple(fold))
        selector.best_estimator = validator.validate_prepared(
            selector.models, folds)
        # preset only once the search SUCCEEDED — a failed search must
        # not leave stale reserved indices for some future fit
        if reserved is not None:
            selector.preset_split = reserved
        return prefitted


class WorkflowModel:
    """A fitted workflow: every origin stage in the result-feature DAG is a
    transformer (reference OpWorkflowModel.scala:58)."""

    def __init__(self, result_features: Tuple[Feature, ...],
                 train_dataset: Optional[Dataset] = None,
                 raw_feature_filter_results=None,
                 blacklisted_feature_names=()):
        self.result_features = tuple(result_features)
        #: transformed training data (all intermediate columns)
        self.train_dataset = train_dataset
        #: RawFeatureFilterResults carried into the fitted model and the
        #: saved op-model.json (reference OpWorkflowModelWriter:75-120 /
        #: ModelInsights.scala:72 — r3 kept them on the Workflow only)
        self.raw_feature_filter_results = raw_feature_filter_results
        self.blacklisted_feature_names = list(blacklisted_feature_names)
        #: directory this model was saved to / loaded from (None for a
        #: purely in-memory model); the serve-time drift sentinel
        #: resolves drift-fingerprints.json through it
        self.model_dir: Optional[str] = None

    def raw_features(self) -> List[Feature]:
        return _unique_raw_features(self.result_features)

    def stages(self) -> List[PipelineStage]:
        return [s for layer in topo_layers(self.result_features)
                for s in layer if not isinstance(s, FeatureGeneratorStage)]

    # -- scoring -----------------------------------------------------------
    def score(self, data: Any = None, keep_intermediate: bool = False,
              engine: str = "columnar") -> Dataset:
        """Transform new data through the fitted DAG
        (reference OpWorkflowModel.score:253). ``data`` is a Dataset or
        record iterable; response features may be absent.

        ``engine`` selects the execution path:

        - ``"columnar"`` (default): per-stage host numpy columnar
          kernels, layer by layer.
        - ``"compiled"``: the serving :class:`ScoringPlan` — the DAG
          fused into shape-bucketed jitted XLA programs with per-stage
          numpy fallback (docs/serving.md). Compiled once per model and
          cached; ~identical results (floating-point associativity
          aside), much faster on large batches.
        """
        if engine not in ("columnar", "compiled"):
            raise ValueError(
                f"engine must be 'columnar' or 'compiled', got {engine!r}")
        if engine == "compiled":
            if keep_intermediate:
                raise ValueError(
                    "keep_intermediate is not supported with "
                    "engine='compiled' (intermediates are fused away "
                    "inside the XLA program)")
            return self.scoring_plan().score(data)
        raw = self.raw_features()
        ds = _generate_raw_data(raw, data, require_responses=False)
        layers = topo_layers(self.result_features)
        scored, _ = _fit_and_transform_layers(layers, ds, fit=False)
        if keep_intermediate:
            return scored
        keep = [f.name for f in raw if f.name in scored] + \
               [f.name for f in self.result_features]
        seen, names = set(), []
        for n in keep:
            if n not in seen:
                seen.add(n)
                names.append(n)
        return scored.select(names)

    def scoring_plan(self, **plan_kwargs):
        """The compiled serving plan for this model (built and compiled
        lazily, cached on the model; see serving/plan.py). Pass
        ``min_bucket``/``max_bucket``/``donate`` to rebuild with a
        different bucket policy."""
        from ..serving import ScoringPlan
        cached = getattr(self, "_scoring_plan", None)
        if cached is None or plan_kwargs:
            cached = ScoringPlan(self, **plan_kwargs).compile()
            self._scoring_plan = cached
        return cached

    def score_and_evaluate(self, data: Any, evaluator: Evaluator,
                           label_feature: Optional[Feature] = None,
                           prediction_feature: Optional[Feature] = None
                           ) -> Tuple[Dataset, EvaluationMetrics]:
        """(reference scoreAndEvaluate:290)"""
        scored = self.score(data)
        self._wire_evaluator(evaluator, label_feature, prediction_feature)
        return scored, evaluator.evaluate_all(scored)

    def evaluate(self, data: Any, evaluator: Evaluator,
                 label_feature: Optional[Feature] = None,
                 prediction_feature: Optional[Feature] = None
                 ) -> EvaluationMetrics:
        """(reference evaluate:318)"""
        return self.score_and_evaluate(
            data, evaluator, label_feature, prediction_feature)[1]

    def _wire_evaluator(self, evaluator: Evaluator,
                        label_feature: Optional[Feature],
                        prediction_feature: Optional[Feature]) -> None:
        if evaluator.label_col is None:
            if label_feature is None:
                responses = [f for f in self.raw_features() if f.is_response]
                if len(responses) != 1:
                    raise ValueError(
                        "Cannot infer label column; pass label_feature")
                label_feature = responses[0]
            evaluator.label_col = label_feature.name
        if evaluator.prediction_col is None:
            pred = (prediction_feature if prediction_feature is not None
                    else self.result_features[-1])
            evaluator.prediction_col = pred.name

    def compute_data_up_to(self, feature: Feature, data: Any) -> Dataset:
        """Materialize all columns needed to produce ``feature``
        (reference computeDataUpTo:105). ``feature`` may be the
        pre-training handle; it is resolved into the fitted DAG by uid."""
        feature = self._resolve(feature)
        raw = _unique_raw_features([feature])
        ds = _generate_raw_data(raw, data, require_responses=False)
        layers = topo_layers([feature])
        out, _ = _fit_and_transform_layers(layers, ds, fit=False)
        return out

    # -- explainability ----------------------------------------------------
    def model_insights(self):
        """Post-hoc explainability report
        (reference OpWorkflowModel.modelInsights:162)."""
        from ..insights import extract_model_insights
        return extract_model_insights(self)

    def summary(self) -> str:
        """JSON summary of all stage metadata (reference summary:182)."""
        import json
        return json.dumps(self.model_insights().to_json(), indent=1,
                          default=str)

    def summary_pretty(self) -> str:
        """(reference summaryPretty:204)"""
        insights = self.model_insights()
        parts = [insights.pretty()]
        sel = insights.selected_model
        if sel:
            from ..selector.selector import SelectedModel
            for s in self.stages():
                if isinstance(s, SelectedModel) and s.summary:
                    parts.append(s.summary.pretty())
                    break
        return "\n\n".join(parts)

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        """Persist the fitted DAG to a directory
        (reference OpWorkflowModel.save:218)."""
        from .persistence import save_model
        save_model(self, path)

    @staticmethod
    def load(path: str) -> "WorkflowModel":
        """(reference OpWorkflow.loadModel)"""
        from .persistence import load_model
        return load_model(path)

    def _resolve(self, feature: Feature) -> Feature:
        """Find the fitted-DAG feature with the same uid (features keep
        their uid through copy_with_new_stages)."""
        found: List[Feature] = []

        def visit(f: Feature):
            if f.uid == feature.uid:
                found.append(f)

        for rf in self.result_features:
            rf.traverse(visit)
            if found:
                return found[0]
        raise KeyError(
            f"Feature {feature.name!r} is not part of this workflow model")
